"""Payload sweep benchmark client — the rdma_performance analog
(reference example/rdma_performance/client.cpp:254-266 prints MB/s +
windowed latency percentiles per payload size).

Server side: any echo server, e.g.
    python tools/bench_server.py --listen 127.0.0.1:8001 [--native]
Then:
    python examples/transport_sweep/client.py --server 127.0.0.1:8001 \
        [--sizes 64,4096,65536,1048576] [--threads 4] [--attachment]
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from brpc_tpu.proto import echo_pb2  # noqa: E402
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller,  # noqa: E402
                          Stub)


def percentile(lat, p):
    return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else 0.0


def run_size(stub, size, threads, seconds, use_attachment):
    payload = b"\xab" * size
    stop = threading.Event()
    lats = [[] for _ in range(threads)]

    def worker(idx):
        while not stop.is_set():
            t0 = time.perf_counter()
            if use_attachment:
                cntl = Controller()
                cntl.request_attachment = payload
                stub.Echo(echo_pb2.EchoRequest(message="s"), controller=cntl)
                assert len(cntl.response_attachment) == size
            else:
                r = stub.Echo(echo_pb2.EchoRequest(message="s",
                                                   payload=payload))
                assert len(r.payload) == size
            lats[idx].append(time.perf_counter() - t0)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    lat = sorted(x for lst in lats for x in lst)
    n = len(lat)
    mbps = 2 * size * n / wall / 1e6
    print(f"{size:>9}B  {mbps:10.1f} MB/s  qps={n / wall:9,.0f}  "
          f"avg={sum(lat) / n * 1e6:8.0f}us  "
          f"p90={percentile(lat, 0.90) * 1e6:8.0f}us  "
          f"p99={percentile(lat, 0.99) * 1e6:8.0f}us  "
          f"p999={percentile(lat, 0.999) * 1e6:8.0f}us")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8001")
    ap.add_argument("--sizes", default="64,4096,65536,1048576")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--attachment", action="store_true",
                    help="carry the payload as an attachment (skips pb "
                         "serialization — the bulk-data lane)")
    ap.add_argument("--native", action="store_true",
                    help="use the C++ engine client transport")
    args = ap.parse_args(argv)
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=60000,
                                native_transport=args.native))
    ch.init(args.server)
    stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
    stub.Echo(echo_pb2.EchoRequest(message="warmup"))
    print(f"# sweep against {args.server} threads={args.threads} "
          f"attachment={args.attachment} native={args.native}")
    for size in (int(s) for s in args.sizes.split(",")):
        run_size(stub, size, args.threads, args.seconds, args.attachment)
    return 0


if __name__ == "__main__":
    sys.exit(main())
