"""Mongo wire-protocol demo: a fake-mongod (MongoService) served by the
framework, driven by the mongo client channel — insert + find over OP_MSG
with our BSON codec (reference example: mongo_c++).

    python examples/mongo_kv/client.py [-n 5]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from brpc_tpu.policy import bson  # noqa: E402
from brpc_tpu.policy.mongo_protocol import (MongoRequest,  # noqa: E402
                                            MongoService, mongo_method)
from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=5)
    args = ap.parse_args(argv)

    store = {}
    svc = MongoService()
    svc.add_command_handler("insert", lambda doc: (
        [store.__setitem__(str(d["_id"]), d)
         for d in doc.get("documents", [])],
        {"ok": 1.0, "n": len(doc.get("documents", []))})[-1])
    svc.add_command_handler("find", lambda doc: {
        "ok": 1.0, "cursor": {"id": 0, "ns": f"demo.{doc['find']}",
                              "firstBatch": [
                                  d for d in store.values()
                                  if all(d.get(k) == v for k, v in
                                         doc.get("filter", {}).items())]}})

    server = Server(ServerOptions(mongo_service=svc))
    server.start("127.0.0.1:0")
    print(f"fake mongod on {server.listen_endpoint()}")

    ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=5000))
    ch.init(str(server.listen_endpoint()))

    def call(doc):
        return ch.call_method(mongo_method(), MongoRequest(doc))

    assert call({"ping": 1, "$db": "admin"}).ok
    docs = [{"_id": bson.ObjectId(), "k": f"key{i}", "v": i * 10}
            for i in range(args.n)]
    r = call({"insert": "kv", "$db": "demo", "documents": docs})
    print(f"inserted n={r.document['n']}")
    for i in range(args.n):
        r = call({"find": "kv", "$db": "demo", "filter": {"k": f"key{i}"}})
        batch = r.document["cursor"]["firstBatch"]
        print(f"find key{i} -> v={batch[0]['v']}")
        assert batch[0]["v"] == i * 10
    server.stop()
    server.join()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
