"""rpc_view proxy example (reference tools/rpc_view): browse a server's
builtin pages THROUGH a proxy that speaks the binary protocol to it.

    python examples/dashboard_proxy/client.py
"""

import sys

from brpc_tpu.policy.http_protocol import http_fetch
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, ServerOptions, Service
from tools import rpc_view


class Echo(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


def main(argv=None) -> int:
    backend = Server(ServerOptions())
    backend.add_service(Echo())
    backend.start("127.0.0.1:0")
    proxy = None
    try:
        proxy = rpc_view.serve("127.0.0.1:0",
                               str(backend.listen_endpoint()), block=False)
        pep = str(proxy.listen_endpoint())
        resp = http_fetch(pep, "GET", "/status", timeout=5)
        assert resp.status == 200 and b"EchoService" in resp.body
        print(f"browsed backend {backend.listen_endpoint()} through proxy "
              f"http://{pep}/status over trpc_std OK")
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
            proxy.join(timeout=5)
        backend.stop()
        backend.join(timeout=5)


if __name__ == "__main__":
    raise SystemExit(main())
