"""gRPC server example (reference example/grpc_c++): any gRPC client can
call this — the port speaks h2c gRPC alongside every other protocol.

    python examples/grpc_echo/server.py [--port 8020]
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, Service


class EchoServiceImpl(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8020)
    ap.add_argument("--run_seconds", type=float, default=0)
    args = ap.parse_args(argv)
    server = Server().add_service(EchoServiceImpl())
    server.start(f"0.0.0.0:{args.port}")
    print(f"gRPC server on {server.listen_endpoint()} "
          f"(grpc.health.v1.Health served builtin)", flush=True)
    try:
        time.sleep(args.run_seconds or 1e9)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
