"""gRPC client example.

    python examples/grpc_echo/client.py [--server 127.0.0.1:8020] [-n 10]
"""

import argparse
import sys

from brpc_tpu.proto import echo_pb2, health_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Stub


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8020")
    ap.add_argument("-n", type=int, default=10)
    args = ap.parse_args(argv)

    ch = Channel(ChannelOptions(protocol="grpc")).init(args.server)
    stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
    for i in range(args.n):
        resp = stub.Echo(echo_pb2.EchoRequest(message=f"grpc {i}"))
        print("Received:", resp.message, flush=True)
    health = Stub(ch, health_pb2.DESCRIPTOR.services_by_name["Health"])
    status = health.Check(health_pb2.HealthCheckRequest()).status
    print("health:", health_pb2.HealthCheckResponse.ServingStatus.Name(status))
    print(ch.latency_recorder.describe())
    return 0


if __name__ == "__main__":
    sys.exit(main())
