"""Batched-inference echo server (brpc_tpu/batch/ — the execution_queue
analog turned into continuous batching).

    python examples/batched_inference/server.py [--port 8014]

`Infer` is declared with @batched_method: concurrent RPCs coalesce into
ONE jitted forward pass per flush (size, deadline, or poll-batch
boundary, whichever first), padded to a declared bucket so the jit cache
stays bounded. Requests reuse EchoRequest: ``payload`` carries DIM
float32 features, the response message is the output row's checksum.

Watch the coalescing live while the client runs:
    curl localhost:8014/vars/g_batch_size
    curl localhost:8014/vars/g_batch_queue_delay_us
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from brpc_tpu.batch import batched_method  # noqa: E402
from brpc_tpu.proto import echo_pb2  # noqa: E402
from brpc_tpu.rpc import Server, Service, errors  # noqa: E402

DIM = 64


class BatchedInferenceService(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def __init__(self):
        import jax

        self._W = np.asarray(
            jax.random.normal(jax.random.PRNGKey(0), (DIM, DIM)))

        @jax.jit
        def fwd(x):  # (B, DIM) -> (B, DIM)
            return jax.nn.relu(x @ self._W)

        self._fwd = fwd
        super().__init__()
        # pre-warm the buckets so first-compile never lands on a request
        for b in (1, 4, 16):
            fwd(np.zeros((b, DIM), np.float32)).block_until_ready()

    @batched_method(max_batch_size=16, max_delay_us=2000,
                    bucket_shapes=(1, 4, 16))
    def Echo(self, batch):
        rows = []
        for i, req in enumerate(batch.requests):
            x = np.frombuffer(req.payload, np.float32)
            if x.shape != (DIM,):
                # one malformed request fails alone; its batchmates ride on
                batch.fail(i, errors.EREQUEST,
                           f"want {DIM} float32 features, got {x.size}")
                x = np.zeros(DIM, np.float32)
            rows.append(x)
        y = self._fwd(batch.stack(rows))     # ONE call for the whole batch
        sums = np.asarray(y.sum(axis=1))
        return [echo_pb2.EchoResponse(
                    message=f"batch={batch.size} sum={float(sums[i]):.4f}")
                for i in range(batch.size)]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8014)
    ap.add_argument("--run_seconds", type=float, default=0,
                    help="exit after N seconds (0 = forever)")
    args = ap.parse_args(argv)

    server = Server()
    server.add_service(BatchedInferenceService())
    server.start(f"0.0.0.0:{args.port}")
    print(f"BatchedInference listening on {server.listen_endpoint()}",
          flush=True)
    try:
        if args.run_seconds:
            time.sleep(args.run_seconds)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
