"""Batched-inference client: fire a pipelined async burst and show that
the server answered it as a handful of vectorized calls.

    python examples/batched_inference/client.py [--server 127.0.0.1:8014]

Each response's message carries the batch size it rode in
(``batch=N sum=...``) — a burst of 32 typically comes back in a few
batches of up to 16 rather than 32 singletons.
"""

import argparse
import collections
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from brpc_tpu.proto import echo_pb2  # noqa: E402
from brpc_tpu.rpc import Channel, ChannelOptions, Stub  # noqa: E402

DIM = 64


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8014")
    ap.add_argument("-n", type=int, default=32)
    ap.add_argument("--timeout_ms", type=int, default=10000)
    args = ap.parse_args(argv)

    channel = Channel(ChannelOptions(timeout_ms=args.timeout_ms))
    channel.init(args.server)
    stub = Stub(channel, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])

    done_ev = threading.Event()
    results = []
    lock = threading.Lock()

    def done(cntl):
        with lock:
            results.append(cntl)
            if len(results) == args.n:
                done_ev.set()

    rng = np.random.default_rng(0)
    for _ in range(args.n):
        x = rng.standard_normal(DIM).astype(np.float32)
        stub.Echo(echo_pb2.EchoRequest(message="infer", payload=x.tobytes()),
                  done=done)
    if not done_ev.wait(30):
        print(f"timed out: {len(results)}/{args.n} done", file=sys.stderr)
        return 1

    sizes = collections.Counter()
    for cntl in results:
        if cntl.failed():
            print(f"FAILED: {cntl.error_code} {cntl.error_text}")
            continue
        msg = cntl._response.message          # "batch=N sum=..."
        sizes[int(msg.split("batch=")[1].split(" ")[0])] += 1
    print(f"{len(results)} responses; items per observed batch size:")
    for size in sorted(sizes, reverse=True):
        print(f"  batch={size:<3d} carried {sizes[size]} request(s)")
    coalesced = sum(n for s, n in sizes.items() if s > 1)
    print(f"{coalesced}/{args.n} requests rode a multi-request batch")
    return 0


if __name__ == "__main__":
    sys.exit(main())
