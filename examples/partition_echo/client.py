"""PartitionChannel example (reference example/partition_echo_c++): shard
one logical service over N partition servers discovered through a naming
service whose tags say which partition each server holds ("i/n" syntax).

    python examples/partition_echo/client.py [--partitions 3] [-n 4]
"""

import argparse
import sys

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, MethodDescriptor, Server, Service
from brpc_tpu.rpc.combo_channels import PartitionChannel, ResponseMerger

ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class PartitionEcho(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def __init__(self, index):
        super().__init__()
        self.index = index

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=f"p{self.index};")


class ConcatMerger(ResponseMerger):
    def merge(self, response, sub):
        response.message += sub.message
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--partitions", type=int, default=3)
    ap.add_argument("-n", type=int, default=4)
    args = ap.parse_args(argv)

    n = args.partitions
    servers = [Server().add_service(PartitionEcho(i)).start("127.0.0.1:0")
               for i in range(n)]
    # list:// naming service with "i/n" partition tags (the reference's
    # PartitionParser syntax)
    ns = "list://" + ",".join(
        f"{s.listen_endpoint()} {i}/{n}" for i, s in enumerate(servers))
    print("naming service:", ns, flush=True)

    pc = PartitionChannel()
    pc.init(ns, n, response_merger=ConcatMerger())
    for i in range(args.n):
        resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message=f"q{i}"))
        print(f"request {i} -> {resp.message}", flush=True)
        assert sorted(resp.message.strip(";").split(";")) == \
            [f"p{k}" for k in range(n)]
    for s in servers:
        s.stop()
        s.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
