"""ParallelChannel fan-out example (reference example/parallel_echo_c++):
spins up N echo servers in-process, fans each request out to all of them,
and merges the responses.

    python examples/parallel_echo/client.py [--servers 3] [-n 5]
"""

import argparse
import sys

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, MethodDescriptor, Server, Service
from brpc_tpu.rpc.combo_channels import ParallelChannel, ResponseMerger

ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class NamedEcho(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def __init__(self, name):
        super().__init__()
        self.name = name

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=f"[{self.name}]")


class ConcatMerger(ResponseMerger):
    def merge(self, response, sub):
        response.message += sub.message
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--servers", type=int, default=3)
    ap.add_argument("-n", type=int, default=5)
    args = ap.parse_args(argv)

    servers = [Server().add_service(NamedEcho(f"srv{i}")).start("127.0.0.1:0")
               for i in range(args.servers)]
    pc = ParallelChannel()
    for s in servers:
        pc.add_channel(Channel().init(str(s.listen_endpoint())),
                       response_merger=ConcatMerger())
    for i in range(args.n):
        resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message=f"r{i}"))
        print(f"request {i} -> merged {resp.message}", flush=True)
    for s in servers:
        s.stop()
        s.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
