"""Backup-request (hedging) example (reference example/backup_request_c++):
two replicas, one slow — the backup timer fires a duplicate attempt and the
fast replica's answer wins, cutting tail latency.

    python examples/backup_request/client.py [-n 10]
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    MethodDescriptor,
    Server,
    Service,
)

ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class Replica(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def __init__(self, name, delay_s=0.0):
        super().__init__()
        self.name = name
        self.delay_s = delay_s

    def Echo(self, cntl, request, done):
        if self.delay_s:
            time.sleep(self.delay_s)
        return echo_pb2.EchoResponse(message=self.name)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=10)
    ap.add_argument("--backup_ms", type=int, default=30)
    args = ap.parse_args(argv)

    slow = Server().add_service(Replica("slow", delay_s=0.5)).start("127.0.0.1:0")
    fast = Server().add_service(Replica("fast")).start("127.0.0.1:0")
    ns = f"list://{slow.listen_endpoint()},{fast.listen_endpoint()}"
    ch = Channel(ChannelOptions(backup_request_ms=args.backup_ms,
                                timeout_ms=2000))
    ch.init(ns, "rr")
    hedged = 0
    for i in range(args.n):
        cntl = Controller()
        t0 = time.time()
        resp = ch.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"),
                              controller=cntl)
        ms = (time.time() - t0) * 1e3
        if cntl._backup_sent:
            hedged += 1
        print(f"call {i}: answered by {resp.message} in {ms:.1f}ms "
              f"(backup={'yes' if cntl._backup_sent else 'no'})", flush=True)
    print(f"{hedged}/{args.n} calls hedged; without backup requests every "
          f"other call would wait 500ms")
    for s in (slow, fast):
        s.stop()
        s.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
