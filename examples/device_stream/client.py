"""Streaming-into-HBM client (tpu/device_stream.py).

Stages chunks into the server's HBM (Put — the one host->device
crossing), then streams the HANDLES: the stream's credit window counts
the HBM bytes the records name, so the producer stalls exactly when the
server's chip holds `--window` bytes of unconsumed blocks. Payload bytes
never transit Python again after the Put.

    python examples/device_stream/server.py
    python examples/device_stream/client.py [--server 127.0.0.1:8310]
"""

import argparse
import sys
import time

from brpc_tpu.proto import device_lane_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub
from brpc_tpu.rpc.stream import get_stream, stream_close
from brpc_tpu.tpu.device_stream import open_device_stream, send_handle


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8310")
    ap.add_argument("-n", type=int, default=8, help="blocks to stream")
    ap.add_argument("--block-kb", type=int, default=256)
    ap.add_argument("--window-kb", type=int, default=512,
                    help="HBM occupancy budget (credit window)")
    args = ap.parse_args(argv)

    dsvc = device_lane_pb2.DESCRIPTOR.services_by_name["DeviceDataService"]
    ch = Channel(ChannelOptions(timeout_ms=30000)).init(args.server)
    put = Stub(ch, dsvc)

    sid = open_device_stream(args.server,
                             window_bytes=args.window_kb << 10)
    total = 0
    t0 = time.perf_counter()
    for i in range(args.n):
        cntl = Controller()
        cntl.request_attachment = bytes([i & 0xFF]) * (args.block_kb << 10)
        h = put.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
        rc = send_handle(sid, h.handle, h.nbytes, timeout=30)
        assert rc == 0, f"send_handle rc={rc}"
        total += h.nbytes
        print(f"streamed block {i}: handle={h.handle} "
              f"({h.nbytes >> 10} KB)", flush=True)
    # credit equality == completion (receivers flush exact feedback)
    st = get_stream(sid)
    deadline = time.time() + 30
    while st._remote_consumed < total and time.time() < deadline:
        time.sleep(0.01)
    wall = time.perf_counter() - t0
    stream_close(sid)
    assert st._remote_consumed >= total, "credits never returned"
    print(f"consumed on-device: {total >> 10} KB in {wall*1e3:.0f} ms "
          f"(window {args.window_kb} KB)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
