"""Streaming-into-HBM server (tpu/device_stream.py, SURVEY §5.7).

Accepts device streams on the Echo RPC: each incoming 16-byte handle
record is consumed ON-DEVICE (transient copy) and freed, and credits
flow back through the stream's feedback — the credit window bounds this
process's device-pool occupancy.

    python examples/device_stream/server.py [--listen 127.0.0.1:8310]
"""

import argparse
import signal
import sys

from brpc_tpu.rpc import Server
from brpc_tpu.tpu.device_lane import DeviceDataService
from brpc_tpu.tpu.device_stream import DeviceStreamEchoService


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", default="127.0.0.1:8310")
    args = ap.parse_args(argv)
    server = Server()
    dds = DeviceDataService()
    server.add_service(dds)
    server.add_service(DeviceStreamEchoService(dds.store))
    server.start(args.listen)
    print(f"device-stream server on {server.listen_endpoint()}",
          flush=True)
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        signal.pause()
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
