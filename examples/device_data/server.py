"""Device-resident payload server: this process owns the chip; clients
orchestrate HBM-resident data over any transport (tpu:// shm tunnel here).

    python examples/device_data/server.py [--listen tpu://127.0.0.1:8300/0]
"""

import argparse
import signal
import sys

from brpc_tpu.rpc import Server, ServerOptions
from brpc_tpu.tpu.device_lane import DeviceDataService


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", default="tpu://127.0.0.1:8300/0")
    args = ap.parse_args(argv)
    server = Server(ServerOptions(native_dataplane=True))
    server.add_service(DeviceDataService())
    server.start(args.listen)
    print(f"DeviceDataService on {server.listen_endpoint()} "
          f"(dashboard: /status /vars /rpcz)", flush=True)
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        signal.pause()
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
