"""Device-resident payload example (brpc_tpu/tpu/device_lane.py).

The ICI-analog workflow: a client ships a tensor into the serving
process's HBM once (Put), orchestrates on-device movement by handle
(Copy / Pump — the data plane never touches the host), checks the
resident/moved accounting (Stats), and pulls bytes back only when it
actually needs them (Get).

Run a server first (any transport; the shm tunnel shown here):

    python examples/device_data/server.py --listen tpu://127.0.0.1:8300/0
    python examples/device_data/client.py --server tpu://127.0.0.1:8300/0
"""

import argparse
import sys

from brpc_tpu.proto import device_lane_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="tpu://127.0.0.1:8300/0")
    ap.add_argument("--mb", type=int, default=4, help="payload MB")
    ap.add_argument("--copies", type=int, default=8)
    ap.add_argument("--pump-rounds", type=int, default=4)
    args = ap.parse_args(argv)

    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=120000,
                                native_transport=True))
    ch.init(args.server)
    stub = Stub(ch, device_lane_pb2.DESCRIPTOR.services_by_name[
        "DeviceDataService"])

    blob = bytes(range(256)) * (args.mb * 4096)
    cntl = Controller()
    cntl.request_attachment = blob
    put = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
    print(f"Put: handle={put.handle} ({put.nbytes >> 20} MB now in HBM)")

    h = put.handle
    for i in range(args.copies):
        h = stub.Copy(device_lane_pb2.DeviceHandle(handle=h)).handle
    print(f"Copy x{args.copies}: final handle={h} (moved on-device only)")

    pumped = stub.Pump(device_lane_pb2.PumpRequest(
        handle=h, rounds=args.pump_rounds))
    print(f"Pump x{args.pump_rounds}: checksum={pumped.checksum} "
          f"moved={pumped.moved_bytes >> 20} MB through HBM (verified)")

    st = stub.Stats(device_lane_pb2.DeviceStatsRequest(fence=True))
    print(f"Stats: {st.handles} handles, {st.resident_bytes >> 20} MB "
          f"resident, {st.moved_bytes >> 20} MB moved")

    back = Controller()
    got = stub.Get(device_lane_pb2.DeviceHandle(handle=h), controller=back)
    assert back.response_attachment == blob, "HBM round trip corrupted data"
    print(f"Get: {got.nbytes >> 20} MB back on the host, content verified")

    stub.Free(device_lane_pb2.DeviceHandle(handle=h))
    stub.Free(device_lane_pb2.DeviceHandle(handle=put.handle))
    return 0


if __name__ == "__main__":
    sys.exit(main())
