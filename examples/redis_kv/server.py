"""Redis-speaking server example (reference example/redis_c++/redis_server):
redis-cli can SET/GET/DEL against this process.

    python examples/redis_kv/server.py [--port 8030]
    redis-cli -p 8030 set k v ; redis-cli -p 8030 get k
"""

import argparse
import sys
import time

from brpc_tpu.policy.redis_protocol import (
    REPLY_BULK,
    REPLY_INTEGER,
    REPLY_STRING,
    RedisReply,
    RedisService,
)
from brpc_tpu.rpc import Server, ServerOptions


def build_service():
    store = {}
    svc = RedisService()
    svc.add_command_handler(
        "set", lambda a: (store.__setitem__(a[1], a[2]),
                          RedisReply(REPLY_STRING, "OK"))[1])
    svc.add_command_handler(
        "get", lambda a: RedisReply(REPLY_BULK, store.get(a[1])))
    svc.add_command_handler(
        "del", lambda a: RedisReply(
            REPLY_INTEGER, 1 if store.pop(a[1], None) is not None else 0))
    svc.add_command_handler(
        "dbsize", lambda a: RedisReply(REPLY_INTEGER, len(store)))
    return svc


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8030)
    ap.add_argument("--run_seconds", type=float, default=0)
    args = ap.parse_args(argv)
    server = Server(ServerOptions(redis_service=build_service()))
    server.start(f"0.0.0.0:{args.port}")
    print(f"redis-compatible server on {server.listen_endpoint()}", flush=True)
    try:
        time.sleep(args.run_seconds or 1e9)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
