"""Streaming LLM client: opens a stream per request, prints tokens as
TokenDelta frames arrive, and reports TTFT vs full-generation latency.

    python examples/llm_server/client.py [--server 127.0.0.1:8011] \
        [--prompt_len 32] [--max_new_tokens 24] [-n 4]
"""

import argparse
import sys
import threading
import time

from brpc_tpu.proto import serving_pb2
from brpc_tpu.rpc import Channel, Controller, Stub
from brpc_tpu.rpc.stream import StreamOptions, stream_close, stream_create

DESC = serving_pb2.DESCRIPTOR.services_by_name["LlmService"]


def generate(stub, prompt_len: int, max_new: int, label: str) -> int:
    toks = []
    t_first = [0.0]
    got_final = threading.Event()

    def on_received(sid, msgs):
        for raw in msgs:
            delta = serving_pb2.TokenDelta()
            delta.ParseFromString(raw)
            if not toks:
                t_first[0] = time.monotonic()
            toks.extend(delta.tokens)
            print(f"  [{label}] += {list(delta.tokens)}", flush=True)
            if delta.done:
                got_final.set()

    sid = stream_create(StreamOptions(on_received=on_received))
    cntl = Controller()
    cntl.stream_id = sid
    cntl.timeout_ms = 60000
    t0 = time.monotonic()
    resp = stub.Generate(
        serving_pb2.GenerateRequest(prompt_len=prompt_len,
                                    max_new_tokens=max_new),
        controller=cntl)
    t_done = time.monotonic()
    if cntl.failed():
        print(f"  [{label}] FAILED: {cntl.error_text()}")
        stream_close(sid)
        return 1
    got_final.wait(timeout=5)
    ttft_ms = (t_first[0] - t0) * 1e3 if t_first[0] else float("nan")
    total_ms = (t_done - t0) * 1e3
    print(f"  [{label}] {len(resp.tokens)} tokens, "
          f"ttft {ttft_ms:.1f}ms < total {total_ms:.1f}ms, "
          f"finish={resp.finish_reason}", flush=True)
    stream_close(sid)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8011")
    ap.add_argument("--prompt_len", type=int, default=32)
    ap.add_argument("--max_new_tokens", type=int, default=24)
    ap.add_argument("-n", type=int, default=4,
                    help="concurrent generations")
    args = ap.parse_args(argv)

    ch = Channel().init(args.server)
    stub = Stub(ch, DESC)
    # warmup: populates the server's jit caches so the timed runs below
    # measure serving, not compilation
    generate(stub, args.prompt_len, 2, "warmup")

    threads = []
    rc = [0] * args.n
    for i in range(args.n):
        def run(i=i):
            rc[i] = generate(stub, args.prompt_len + i,
                             args.max_new_tokens, f"req{i}")
        t = threading.Thread(target=run)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return 1 if any(rc) else 0


if __name__ == "__main__":
    sys.exit(main())
