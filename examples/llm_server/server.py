"""LLM serving example — the serving plane's flagship server.

Continuous-batching engine (paged KV over DeviceStore, iteration-level
scheduling) behind LlmService, with token streaming over the Stream API.
Browse http://<host>:<port>/serving while the client runs to watch batch
occupancy and the KV watermark.

    python examples/llm_server/server.py [--port 8011] [--scheduling continuous]
"""

import argparse
import sys
import time

from brpc_tpu.rpc import Server
from brpc_tpu.serving import (
    EngineConfig,
    KVCacheConfig,
    LlmServingService,
    ModelConfig,
    PagedKVCache,
    ServingEngine,
    TinyTransformer,
)


def build_engine(args) -> ServingEngine:
    model_cfg = ModelConfig(vocab=args.vocab, d_model=args.d_model,
                            n_heads=args.n_heads, n_layers=args.n_layers)
    kv = PagedKVCache(
        KVCacheConfig(block_size=args.block_size,
                      num_blocks=args.num_blocks,
                      watermark=args.watermark),
        model_cfg.n_layers, model_cfg.kv_dim)
    model = TinyTransformer(model_cfg, kv)
    engine = ServingEngine(model, kv, EngineConfig(
        max_batch=args.max_batch, token_budget=args.token_budget,
        scheduling=args.scheduling))
    return engine.start()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8011)
    ap.add_argument("--scheduling", choices=("continuous", "static"),
                    default="continuous")
    ap.add_argument("--max_batch", type=int, default=8)
    ap.add_argument("--token_budget", type=int, default=512)
    ap.add_argument("--block_size", type=int, default=16)
    ap.add_argument("--num_blocks", type=int, default=256)
    ap.add_argument("--watermark", type=float, default=0.90)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--d_model", type=int, default=64)
    ap.add_argument("--n_heads", type=int, default=4)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--run_seconds", type=float, default=0)
    args = ap.parse_args(argv)

    engine = build_engine(args)
    server = Server().add_service(LlmServingService(engine))
    server.start(f"0.0.0.0:{args.port}")
    print(f"LlmServer on {server.listen_endpoint()} "
          f"({args.scheduling} batching, "
          f"{args.num_blocks}x{args.block_size}-token KV blocks) — "
          f"see /serving", flush=True)
    try:
        time.sleep(args.run_seconds or 1e9)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    engine.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
