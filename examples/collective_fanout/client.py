"""Collective-lowered ParallelChannel (SURVEY §2.5; round 4).

The SAME ParallelChannel fan-out executes two ways:

  1. every sub-channel targets a local tpu:// device  -> ONE shard_map
     program over a mesh built from those devices (the merger IS the
     collective: sum -> psum, gather -> sharded assembly)
  2. forced RPC fallback -> one CollectiveService.Apply per sub-channel
     through the device-method lane, merged host-side

and the results agree bit-for-bit.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/collective_fanout/client.py
"""

import argparse
import os
import sys

# the demo pins itself to a virtual 8-device CPU mesh so it runs the
# same everywhere (on multi-TPU hosts, drop these two lines and the
# same code lowers onto the real chips)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    print("jax unavailable; example skipped")
    sys.exit(0)

import numpy as np

from brpc_tpu.rpc import Channel
from brpc_tpu.rpc.combo_channels import CollectiveScheme, ParallelChannel


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8)
    args = p.parse_args(argv)

    n = min(args.devices, len(jax.devices()))
    pc = ParallelChannel()
    for i in range(n):
        pc.add_channel(Channel().init(f"tpu://localhost/{i}"))

    scheme = CollectiveScheme("example.scale", fn=lambda s: s * 3.0,
                              merge="sum")
    x = np.arange(n * 4, dtype=np.float32).reshape(n * 2, 2)

    mesh = pc.device_mesh(scheme.axis_name)
    print(f"sub-channels: {n} tpu:// devices; mesh detected: "
          f"{mesh is not None}")
    out_collective = np.asarray(pc.call_tensor(x, scheme))
    out_rpc = np.asarray(pc._call_tensor_rpc(x, scheme))
    assert np.allclose(out_collective, out_rpc), "paths diverged!"
    print(f"shard_map result == {n}-RPC fallback result "
          f"(shape {out_collective.shape}) OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
