"""Echo server (reference example/echo_c++/server.cpp).

    python examples/echo/server.py [--port 8000]

While it runs, the same port serves the builtin dashboard:
    curl localhost:8000/status   curl localhost:8000/vars
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, ServerOptions, Service


class EchoServiceImpl(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        # attachments round-trip untouched by serialization, like the
        # reference example demonstrates
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--idle_timeout_s", type=int, default=-1)
    ap.add_argument("--run_seconds", type=float, default=0,
                    help="exit after N seconds (0 = forever)")
    args = ap.parse_args(argv)

    server = Server(ServerOptions(idle_timeout_s=args.idle_timeout_s))
    server.add_service(EchoServiceImpl())
    server.start(f"0.0.0.0:{args.port}")
    print(f"EchoServer listening on {server.listen_endpoint()}", flush=True)
    try:
        if args.run_seconds:
            time.sleep(args.run_seconds)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
