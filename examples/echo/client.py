"""Echo client (reference example/echo_c++/client.cpp).

    python examples/echo/client.py [--server 127.0.0.1:8000] [-n 10]
"""

import argparse
import sys

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8000")
    ap.add_argument("--protocol", default="trpc_std")
    ap.add_argument("--timeout_ms", type=int, default=1000)
    ap.add_argument("-n", type=int, default=10)
    ap.add_argument("--attachment", default="echo attachment")
    args = ap.parse_args(argv)

    channel = Channel(ChannelOptions(protocol=args.protocol,
                                     timeout_ms=args.timeout_ms))
    channel.init(args.server)
    stub = Stub(channel, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])

    for i in range(args.n):
        cntl = Controller()
        cntl.request_attachment = args.attachment.encode()
        resp = stub.Echo(echo_pb2.EchoRequest(message=f"hello {i}"),
                         controller=cntl)
        print(f"Received: {resp.message!r} attachment="
              f"{cntl.response_attachment!r} latency={cntl.latency_us}us",
              flush=True)
    print(channel.latency_recorder.describe())
    return 0


if __name__ == "__main__":
    sys.exit(main())
