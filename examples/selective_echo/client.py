"""SelectiveChannel example (reference example/selective_echo_c++): LB over
channels — each call picks one healthy sub-channel; failures steer traffic
to survivors.

    python examples/selective_echo/client.py [--servers 3] [-n 12]
"""

import argparse
import sys

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, MethodDescriptor, Server, Service
from brpc_tpu.rpc.combo_channels import SelectiveChannel

ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class NamedEcho(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def __init__(self, name):
        super().__init__()
        self.name = name
        self.hits = 0

    def Echo(self, cntl, request, done):
        self.hits += 1
        return echo_pb2.EchoResponse(message=self.name)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--servers", type=int, default=3)
    ap.add_argument("-n", type=int, default=12)
    args = ap.parse_args(argv)

    impls = [NamedEcho(f"srv{i}") for i in range(args.servers)]
    servers = [Server().add_service(im).start("127.0.0.1:0") for im in impls]
    sc = SelectiveChannel()
    for s in servers:
        sc.add_channel(Channel().init(str(s.listen_endpoint())))
    for i in range(args.n):
        resp = sc.call_method(ECHO_MD, echo_pb2.EchoRequest(message=f"r{i}"))
        print(f"request {i} answered by {resp.message}", flush=True)
    # kill one server: traffic must flow to the survivors
    servers[0].stop()
    servers[0].join()
    print("-- killed srv0 --", flush=True)
    for i in range(args.n):
        resp = sc.call_method(ECHO_MD, echo_pb2.EchoRequest(message=f"k{i}"))
        assert resp.message != "srv0"
        print(f"request {i} answered by {resp.message}", flush=True)
    print("hits:", {im.name: im.hits for im in impls})
    for s in servers[1:]:
        s.stop()
        s.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
