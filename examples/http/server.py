"""HTTP server example (reference example/http_c++): the same port answers
pb-RPC, restful JSON, and the builtin dashboard.

    python examples/http/server.py [--port 8010]
    curl localhost:8010/EchoService/Echo -d '{"message":"hi"}'
    curl localhost:8010/status
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, Service


class EchoServiceImpl(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        if cntl.http_request is not None:
            print(f"via HTTP {cntl.http_request.method} "
                  f"{cntl.http_request.path}", flush=True)
        return echo_pb2.EchoResponse(message=request.message)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8010)
    ap.add_argument("--run_seconds", type=float, default=0)
    args = ap.parse_args(argv)
    server = Server().add_service(EchoServiceImpl())
    server.start(f"0.0.0.0:{args.port}")
    print(f"HTTP+RPC server on {server.listen_endpoint()}", flush=True)
    try:
        time.sleep(args.run_seconds or 1e9)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
