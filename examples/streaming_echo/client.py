"""Streaming echo client (reference example/streaming_echo_c++/client.cpp):
opens a stream over the Echo RPC, pushes N messages, awaits the echoes.

    python examples/streaming_echo/client.py [--server 127.0.0.1:8001] [-n 100]
"""

import argparse
import sys
import threading

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, Controller, Stub
from brpc_tpu.rpc.stream import (
    StreamOptions,
    stream_close,
    stream_create,
    stream_write,
)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:8001")
    ap.add_argument("-n", type=int, default=100)
    ap.add_argument("--message_bytes", type=int, default=64)
    args = ap.parse_args(argv)

    got = []
    done = threading.Event()

    def on_received(sid, msgs):
        got.extend(msgs)
        if len(got) >= args.n:
            done.set()

    sid = stream_create(StreamOptions(on_received=on_received))
    cntl = Controller()
    cntl.stream_id = sid
    ch = Channel().init(args.server)
    stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
    resp = stub.Echo(echo_pb2.EchoRequest(message="open stream"),
                     controller=cntl)
    print(f"RPC reply: {resp.message}", flush=True)

    body = b"m" * args.message_bytes
    for i in range(args.n):
        rc = stream_write(sid, body + str(i).encode())
        if rc != 0:
            print(f"stream_write failed rc={rc}")
            return 1
    if not done.wait(timeout=10):
        print(f"timed out with {len(got)}/{args.n} echoes")
        return 1
    print(f"echoed {len(got)} messages, last={got[-1][-8:]!r}")
    stream_close(sid)
    return 0


if __name__ == "__main__":
    sys.exit(main())
