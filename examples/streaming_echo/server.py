"""Streaming echo server (reference example/streaming_echo_c++/server.cpp):
accepts a stream on the Echo RPC and echoes every message back on it.

    python examples/streaming_echo/server.py [--port 8001]
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, Service
from brpc_tpu.rpc.stream import StreamOptions, stream_accept, stream_write


class StreamingEchoService(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        def on_received(sid, msgs):
            for m in msgs:
                stream_write(sid, m)

        def on_closed(sid):
            print(f"stream {sid} closed", flush=True)

        sid = stream_accept(cntl, StreamOptions(on_received=on_received,
                                                on_closed=on_closed))
        print(f"accepted stream {sid}", flush=True)
        return echo_pb2.EchoResponse(message="stream-accepted")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8001)
    ap.add_argument("--run_seconds", type=float, default=0)
    args = ap.parse_args(argv)
    server = Server().add_service(StreamingEchoService())
    server.start(f"0.0.0.0:{args.port}")
    print(f"StreamingEchoServer on {server.listen_endpoint()}", flush=True)
    try:
        time.sleep(args.run_seconds or 1e9)
    except KeyboardInterrupt:
        pass
    server.stop()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
