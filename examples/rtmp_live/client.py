"""RTMP live relay demo: a server, a publisher pushing synthetic frames,
and a player receiving them (reference example: rtmp_c++ / live relay).

    python examples/rtmp_live/client.py [-n 10]
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from brpc_tpu.policy.rtmp import (MSG_AUDIO, MSG_VIDEO, RtmpClient,  # noqa: E402
                                  RtmpService)
from brpc_tpu.rpc import Server, ServerOptions  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=10, help="frames to publish")
    args = ap.parse_args(argv)

    server = Server(ServerOptions(rtmp_service=RtmpService()))
    server.start("127.0.0.1:0")
    ep = server.listen_endpoint()
    print(f"rtmp server on {ep}")

    publisher = RtmpClient(ep.host, ep.port, app="live")
    player = RtmpClient(ep.host, ep.port, app="live")
    got = []
    done = threading.Event()

    def on_frame(mtype, sid, payload):
        kind = {MSG_VIDEO: "video", MSG_AUDIO: "audio"}.get(mtype, "data")
        got.append(kind)
        print(f"[player] {kind} frame {len(payload)}B "
              f"(#{len(got)})")
        if len(got) >= args.n:
            done.set()

    player.on_frame = on_frame
    psid = publisher.create_stream()
    publisher.publish("demo", psid)
    ssid = player.create_stream()
    player.play("demo", ssid)
    publisher.send_metadata(psid, "@setDataFrame",
                            {"width": 1280.0, "height": 720.0, "fps": 30.0})
    for i in range(args.n):
        mtype = MSG_VIDEO if i % 3 != 2 else MSG_AUDIO
        publisher.send_frame(mtype, psid, bytes([i]) * (1000 + i),
                             timestamp=i * 33)
        time.sleep(0.01)
    ok = done.wait(5)
    publisher.close()
    player.close()
    server.stop()
    server.join()
    print(f"relayed {len(got)} frames " + ("OK" if ok else "(incomplete)"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
