"""TPU transfer benchmark (reference example/rdma_performance/client.cpp:
payload sweep printing bandwidth + latency percentiles — here the "wire" is
the device DMA engine instead of an RDMA HCA).

    python examples/tpu_transfer/client.py [--sizes 4096,65536,1048576] [-n 32]

Runs against tpu://0 (first visible device; CPU backend works too, e.g.
under JAX_PLATFORMS=cpu).
"""

import argparse
import sys
import time

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", default="tpu://0")
    ap.add_argument("--sizes", default="4096,65536,1048576")
    ap.add_argument("-n", type=int, default=32)
    args = ap.parse_args(argv)

    ch = Channel(ChannelOptions(timeout_ms=60000)).init(args.device)
    stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])

    print(f"{'size':>10} {'avg_ms':>9} {'p99_ms':>9} {'MB/s':>10}")
    for size in (int(s) for s in args.sizes.split(",")):
        payload = b"\xab" * size
        lats = []
        # warmup (first call compiles the device program)
        stub.Echo(echo_pb2.EchoRequest(message="warm", payload=payload))
        t0 = time.time()
        for _ in range(args.n):
            t1 = time.time()
            resp = stub.Echo(echo_pb2.EchoRequest(message="b",
                                                  payload=payload))
            lats.append((time.time() - t1) * 1e3)
            assert len(resp.payload) == size
        wall = time.time() - t0
        lats.sort()
        mbs = (size * 2 * args.n / wall) / 1e6  # bytes moved both ways
        p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
        print(f"{size:>10} {sum(lats)/len(lats):>9.2f} {p99:>9.2f} "
              f"{mbs:>10.1f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
